"""Pre-warm the persistent compilation cache for the measurement
capacity ladder (VERDICT r3 #4 "kill the compile tax").

tools/compile_probe.py measured where warm-start time goes on the
tunneled TPU: tracing+lowering is ~5s, a COLD backend compile of the
fused step is ~38s, a WARM disk-cache load is ~2s — and a further
~30s floor comes from the many small root-path programs, each paying
the tunnel's per-executable round trip.  So the compile tax has two
parts:

1. cold compiles after a code or capacity-shape change — REMOVABLE by
   running this tool once per code change: it constructs each ladder
   engine and runs a depth-2 check, which exercises every executable
   (step, finalize, root fingerprint/phase2, and the small eager ops)
   and writes them all to the persistent cache (min_compile_time is 0
   since round 4);
2. per-process executable *loads* through the tunnel (~1-3s each, ~10
   executables) — the irreducible ~20-40s floor of this environment;
   on a local (non-tunneled) runtime the same loads are sub-second.

Usage: python tools/prewarm.py [config_no ...]   (default: the bench
config #2 ladder + configs 1-5 at their measure_baseline capacities)
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def warm(tag, cfg, **kw):
    """A depth-2 check per (burst, guard-matmul, delta-matmul) mode:
    the default (burst=True) pass compiles the fused multi-level
    executable the tiny levels run on; the burst=False pass compiles
    the per-level step/finalize pair the engine falls back to the
    moment a level outgrows the burst ring — BOTH are hit by every
    real run, so both land in the persistent cache here.  Round 9:
    each burst mode warms under BOTH matmul modes (the default MXU
    guard-matmul path and the --no-guard-matmul lane sweep); round 11
    adds the delta-matmul successor modes — matmul modes pair with
    their matching delta mode plus the two cross-mode A/B programs
    (gm ON × delta OFF and gm OFF × delta ON), so any
    --[no-]guard-matmul/--[no-]delta-matmul session pays no cold
    compiles."""
    from raft_tla_tpu.engine.bfs import Engine
    t0 = time.time()
    for gm, dm in ((True, True), (True, False),
                   (False, True), (False, False)):
        for burst in (True, False):
            eng = Engine(cfg, store_states=False, burst=burst,
                         guard_matmul=gm, delta_matmul=dm, **kw)
            eng.check(max_depth=2)
    print(f"{tag}: warmed in {time.time() - t0:.1f}s "
          f"(chunk={eng.chunk} LCAP={eng.LCAP} VCAP={eng.VCAP} "
          f"FCAP={eng.FCAP})", flush=True)
    del eng


def warm_spill(tag, cfg, **kw):
    """Spill-engine twin of warm(); with host_table=True the depth-2
    check additionally exercises the partitioned-table executables
    (the sweep membership probe, the cache-reseed insert, and the
    lfp-carrying spill slice), so a post-change deep_run/bench with
    --host-table doesn't pay their cold compiles mid-run.  Like
    warm(), both burst modes run — host-table mode keeps the per-level
    path (the sweep is due every level), so the burst pass is skipped
    there."""
    from raft_tla_tpu.engine.spill import SpillEngine
    t0 = time.time()
    modes = (True, False) if not kw.get("host_table") else (False,)
    # both matmul modes (round 9) × both delta modes (round 11; the
    # cross-mode combinations matter only for the classic engine's
    # A/B sessions — spill warms the two default-paired programs)
    for gm, dm in ((True, True), (False, False)):
        for burst in modes:
            eng = SpillEngine(cfg, store_states=False, burst=burst,
                              guard_matmul=gm, delta_matmul=dm, **kw)
            eng.check(max_depth=2)
    print(f"{tag}: warmed in {time.time() - t0:.1f}s "
          f"(chunk={eng.chunk} SEGL={eng.SEGL} VCAP={eng.VCAP} "
          f"host_table={eng.host_table})", flush=True)
    del eng


def warm_pjit(tag, cfg, **kw):
    """Pjit-engine warm (round 14): the whole-state-sharded program's
    step/finalize/burst executables trace with NamedSharding
    out_shardings, so they are DISTINCT cache entries from the classic
    engine's — one depth-2 check per burst mode lands them (plus the
    sharded fresh-carry builders) in the persistent cache before a
    pod-scale session pays them cold."""
    from raft_tla_tpu.parallel.pjit_mesh import PjitShardedEngine
    t0 = time.time()
    for burst in (True, False):
        eng = PjitShardedEngine(cfg, store_states=False, burst=burst,
                                **kw)
        eng.check(max_depth=2)
    print(f"{tag}: pjit warmed in {time.time() - t0:.1f}s "
          f"(D={eng.D} chunk={eng.chunk} LCAP={eng.LCAP} "
          f"VCAP={eng.VCAP})", flush=True)
    del eng


def warm_sym(tag, cfg, **kw):
    """Canonicalization-mode warm (round 15): a symmetric config
    compiles DISTINCT fingerprint programs under --sym-canon sort
    (argsort canonicalization + transposition certificates + the
    cond-gated min-over-perms fallback) vs minperm (the P-fold min) —
    auto picks exactly one, so a bench _canon_ab or deep_run A/B
    session would pay the other's cold compile mid-run.  One depth-2
    check per mode lands both in the persistent cache."""
    from raft_tla_tpu.engine.bfs import Engine
    t0 = time.time()
    for mode in ("sort", "minperm"):
        eng = Engine(cfg, store_states=False, sym_canon=mode, **kw)
        eng.check(max_depth=2)
    print(f"{tag}: sym-canon modes warmed in {time.time() - t0:.1f}s "
          f"(chunk={eng.chunk} P={len(eng.fpr.sigmas)})", flush=True)
    del eng


def warm_resume(tag, cfg, **kw):
    """Resume-repartition warm (round 12): checkpoint a depth-2 run,
    load the portable image and resume it on the spill engine — this
    exercises the resume-side executables a supervised recovery pays
    mid-incident (the fresh-carry build, the table-image upload, the
    repartitioned first level) so they land in the persistent cache
    before the tunnel ever drops."""
    import tempfile

    from raft_tla_tpu.engine.bfs import Engine
    from raft_tla_tpu.engine.spill import SpillEngine
    from raft_tla_tpu.resil.portable import load_portable_image
    t0 = time.time()
    ck = os.path.join(tempfile.mkdtemp(prefix="prewarm_resil_"),
                      "warm.ckpt")
    eng = Engine(cfg, store_states=False, **kw)
    eng.check(max_depth=2, checkpoint_path=ck, checkpoint_every=1)
    eng.check(max_depth=3, resume_from=ck)           # native resume
    img = load_portable_image(ck)
    sp = SpillEngine(cfg, store_states=False, seg=1 << 14,
                     chunk=kw.get("chunk", 256))
    sp.check(max_depth=3, resume_image=img)          # repartition
    print(f"{tag}: resume/repartition warmed in "
          f"{time.time() - t0:.1f}s", flush=True)


def main():
    from tools.measure_baseline import ENGINE_KW, build_cfg

    # per-spec warming (SpecIR frontends compile distinct programs):
    # "paxos" warms the stock Paxos model's executables — both matmul
    # and burst modes, plus a spill pass — alongside the raft ladder
    raw = sys.argv[1:]
    if "paxos" in raw:
        raw = [a for a in raw if a != "paxos"]
        from raft_tla_tpu.spec.paxos.config import PaxosConfig
        pcfg = PaxosConfig()
        warm("paxos default", pcfg, chunk=256)
        warm_spill("paxos spill", pcfg, chunk=256, seg=1 << 14)
        if not raw:
            return
    args = [int(a) for a in raw]
    # bench.py's shapes first: its micro correctness-gate engine
    # (chunk=256) AND its headline capacities both differ from
    # measure_baseline's budgeted ones — without them a post-prewarm
    # bench run would still pay cold compiles inside its timed session
    if not args:
        import bench
        from raft_tla_tpu.cfg.parser import load_model
        from raft_tla_tpu.config import Bounds
        micro = load_model(
            "/root/reference/tlc_membership/raft.cfg",
            bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                               max_client_requests=1))
        micro = micro.with_(n_servers=2, init_servers=(0, 1),
                            values=(1,), max_inflight_override=4)
        warm("bench micro gate", micro, chunk=256)
        # the supervised-recovery path's executables (round 12)
        warm_resume("resume repartition", micro, chunk=256)
        # the pod-scale sharded program (round 14) — its executables
        # are distinct cache entries from the classic engine's
        warm_pjit("pjit micro", micro, chunk=256)
        # both canonicalization modes (round 15) at bench _canon_ab's
        # exact shape: the config-#5 S=5/P=120 space where auto picks
        # sort — without this the forced-minperm A/B twin compiles cold
        from raft_tla_tpu.config import Bounds as _B, ModelConfig, \
            NEXT_ASYNC
        warm_sym("canon A/B config-5 shape", ModelConfig(
            n_servers=5, init_servers=(0, 1, 2, 3, 4), values=(1,),
            next_family=NEXT_ASYNC, symmetry=True,
            max_inflight_override=4,
            bounds=_B.make(max_log_length=2, max_timeouts=1,
                           max_client_requests=1)), chunk=256)
        warm("bench headline", build_cfg(2), chunk=2048,
             lcap=bench.LCAP, vcap=bench.VCAP)
        # deep_run's spill probe shape, host table OFF and ON: the ON
        # pass compiles the sweep/reseed executables at the ladder's
        # quantized key-block shapes
        warm_spill("spill config 2", build_cfg(2), chunk=4096,
                   seg=1 << 22, vcap=1 << 26)
        warm_spill("spill config 2 +host-table", build_cfg(2),
                   chunk=4096, seg=1 << 22, vcap=1 << 26,
                   host_table=True, partitions=4, part_cap=1 << 16)
    for n in args or [1, 2, 3, 4, 5]:
        warm(f"config {n}", build_cfg(n), **ENGINE_KW[n])


if __name__ == "__main__":
    main()
