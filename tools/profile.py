"""Unified engine profiler, built on the obs span recorder.

Consolidates the three one-off scripts it replaces
(profile_engine.py — compile vs steady-state; profile_config3.py /
profile_config3b.py — per-phase attribution of the fused chunk step on
a captured mid-depth frontier) into two modes sharing one harness:

  steady — jit-compile cost, steady-state chunk-step and finalize
           latency, then a bounded full run with growth logging:
             python tools/profile.py steady [--config N] [--chunk C]
                 [--lcap N] [--vcap N] [--budget N]
  phases — capture a real frontier at --depth via the finalize hook,
           then time the step's phases separately (guard pass,
           expand+materialize+fingerprint, +probe-insert dedup,
           +phase2, full fused step) and print the attribution:
             python tools/profile.py phases [--config N] [--depth D]
                 [--chunk C]

Both modes record every measured region as an obs span, so
``--timeline FILE`` emits the whole profiling session as
Perfetto-loadable Chrome-trace JSON — the same format and span names
the engines' ``--trace-timeline`` uses.

``--config N`` picks the BASELINE config (tools/measure_baseline
.build_cfg; default 2 for steady, 3 for phases).  Containers without
/root/reference fall back to the repo-local configs/ twin at micro
bounds (honestly labeled), so the tool runs anywhere.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax                                               # noqa: E402
import jax.numpy as jnp                                  # noqa: E402
import numpy as np                                       # noqa: E402
from jax import lax                                      # noqa: E402

from raft_tla_tpu.engine.bfs import Engine               # noqa: E402
from raft_tla_tpu.obs import SpanRecorder                # noqa: E402
from raft_tla_tpu.ops.codec import widen                 # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_cfg(n: int):
    """build_cfg(n) when the reference tree exists; otherwise the
    repo-local twin at micro bounds (labeled — the twin parses
    identically, tests/test_sim.py pins that)."""
    if os.path.exists("/root/reference/tlc_membership/raft.cfg"):
        from tools.measure_baseline import ENGINE_KW, build_cfg
        return build_cfg(n), dict(ENGINE_KW.get(n, {}))
    from raft_tla_tpu.cfg.parser import load_model
    from raft_tla_tpu.config import Bounds
    print("NOTE: /root/reference absent — profiling the repo-local "
          "configs/ twin at micro bounds (relative attribution is "
          "meaningful; absolute rates are not the BASELINE shape)",
          flush=True)
    cfg = load_model(
        os.path.join(_REPO, "configs", "tlc_membership", "raft.cfg"),
        bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                           max_client_requests=1))
    return cfg.with_(n_servers=2, init_servers=(0, 1), values=(1,),
                     max_inflight_override=4), dict(chunk=256)


def _bench(rec, name, fn, iters):
    """Compile + steady-state timing of one component, each region a
    span (compile once, then `name` per steady iteration)."""
    with rec.span("compile"):
        v = fn(0)
        jax.tree_util.tree_map(
            lambda x: np.asarray(x) if hasattr(x, "shape") else x, v)
    t0 = time.perf_counter()
    for i in range(iters):
        with rec.span(name):
            v = fn(i)
    jax.tree_util.tree_map(
        lambda x: np.asarray(x) if hasattr(x, "shape") else x, v)
    dt = (time.perf_counter() - t0) / iters
    tc = rec.totals()["compile"]["seconds"]
    print(f"{name:30s} compile {tc:6.1f}s   steady "
          f"{dt * 1000:8.2f} ms", flush=True)
    return dt


def mode_steady(opts, rec):
    conf_no = int(opts.get("--config", 2))
    cfg, kw = load_cfg(conf_no)
    if "--chunk" in opts:
        kw["chunk"] = int(opts["--chunk"])
    if "--lcap" in opts:
        kw["lcap"] = int(opts["--lcap"])
    if "--vcap" in opts:
        kw["vcap"] = int(opts["--vcap"])
    kw.pop("fam_caps", None)
    eng = Engine(cfg, store_states=False, **kw)
    print(f"config #{conf_no}: lanes={eng.A} chunk={eng.chunk} "
          f"LCAP={eng.LCAP} VCAP={eng.VCAP}", flush=True)

    carry = eng._fresh_carry(eng.LCAP, eng.VCAP)
    with rec.span("compile"):
        carry = eng._step_jit(carry, eng.FAM_CAPS)
        jax.block_until_ready(carry["n_lvl"])
    print(f"step compile+run1: "
          f"{rec.totals()['compile']['seconds']:.1f}s", flush=True)
    with rec.span("compile"):
        carry, out = eng._fin_jit(carry)
        jax.block_until_ready(out["scal"])

    # steady state: sync with a real transfer (block_until_ready is
    # unreliable through the axon tunnel — the lesson profile_engine
    # learned)
    t0 = time.perf_counter()
    for _ in range(10):
        with rec.span("level_dispatch"):
            carry = eng._step_jit(carry, eng.FAM_CAPS)
    _ = int(np.asarray(carry["n_lvl"]))
    dt = (time.perf_counter() - t0) / 10
    print(f"steady chunk step: {dt * 1000:.1f} ms -> "
          f"{eng.chunk / dt:.0f} parent-states/s "
          f"({eng.chunk * eng.A / dt:.0f} cand/s)", flush=True)
    t0 = time.perf_counter()
    with rec.span("level_dispatch"):
        carry, out = eng._fin_jit(carry)
        _ = np.asarray(out["scal"])
    print(f"steady finalize: "
          f"{(time.perf_counter() - t0) * 1000:.1f} ms", flush=True)

    # full bounded run with growth logging (fresh engine: the probe
    # carry above dirtied the first one's table); the engine-internal
    # spans (burst_dispatch / level_dispatch / harvest) land on the
    # same recorder, so --timeline shows the whole run's phases
    from raft_tla_tpu.obs import Obs
    eng2 = Engine(cfg, store_states=False, **kw)
    budget = int(opts.get("--budget", 150_000))
    t0 = time.perf_counter()
    with rec.span("check"):
        r = eng2.check(max_states=budget, verbose=True,
                       obs=Obs(spans=rec))
    print(f"full: {r.distinct_states} states depth {r.depth} in "
          f"{time.perf_counter() - t0:.1f}s -> "
          f"{r.states_per_sec:.0f}/s  "
          f"final LCAP={eng2.LCAP} VCAP={eng2.VCAP}", flush=True)


def mode_phases(opts, rec):
    conf_no = int(opts.get("--config", 3))
    cap_depth = int(opts.get("--depth", 13))
    cfg, kw = load_cfg(conf_no)
    if "--chunk" in opts:
        kw["chunk"] = int(opts["--chunk"])
    eng = Engine(cfg, store_states=False, **kw)
    B, A, FCAP = eng.chunk, eng.A, eng.FCAP
    print(f"config #{conf_no}: lanes={A} chunk={B} FCAP={FCAP} "
          f"W={eng.W}", flush=True)

    # ---- capture the carry entering the finalize at cap_depth ----
    snap = {}
    real_fin = eng._fin_jit
    lvl = [0]

    def fin_hook(carry):
        lvl[0] += 1
        if lvl[0] == cap_depth and "c" not in snap:
            # snapshot to host BEFORE donation invalidates the buffers
            snap["c"] = jax.tree_util.tree_map(np.asarray, carry)
        return real_fin(carry)

    eng._fin_jit = fin_hook
    with rec.span("capture"):
        # burst off for the capture: the fused path never calls the
        # finalize hook on the early levels
        eng.burst = False
        r = eng.check(max_depth=cap_depth, max_states=1_500_000)
    eng._fin_jit = real_fin
    if "c" not in snap:
        raise SystemExit(f"space exhausted at depth {r.depth} before "
                         f"--depth {cap_depth}; pass a smaller depth")
    carry = jax.tree_util.tree_map(jnp.asarray, snap["c"])
    carry, out = eng._fin_jit(carry)
    n_front = int(np.asarray(out["scal"])[3])
    print(f"captured frontier: {n_front} rows at depth {cap_depth} "
          f"({r.distinct_states} states explored)", flush=True)

    def chunk_front(carry, base):
        sv = widen({k: lax.dynamic_slice_in_dim(v, base, B,
                                                axis=v.ndim - 1)
                    for k, v in carry["front"].items()})
        fmask = lax.dynamic_slice_in_dim(carry["fmask"], base, B)
        valid = ((base + jnp.arange(B, dtype=jnp.int32)) <
                 carry["n_front"]) & fmask
        return sv, valid

    # ---- component jits (everything consumed so nothing DCEs) ----
    @jax.jit
    def guard_only(carry, base):
        sv, valid = chunk_front(carry, base)
        derb = eng.expander.derived_batch_T(sv)
        ok = eng.expander.guards_T(sv, derb)
        return (ok & valid[:, None]).sum()

    @jax.jit
    def expand_fp(carry, base):
        sv, valid = chunk_front(carry, base)
        cand_c, elive, fp, take, famx, n_e = eng._expand_fp_chunk(
            sv, valid, eng.FAM_CAPS, FCAP)
        s = sum(jnp.sum(v.astype(jnp.int32)) for v in cand_c.values())
        return s + fp.astype(jnp.int32).sum() + n_e + elive.sum()

    @jax.jit
    def expand_fp_probe(carry, base):
        sv, valid = chunk_front(carry, base)
        cand_c, elive, fp, take, famx, n_e = eng._expand_fp_chunk(
            sv, valid, eng.FAM_CAPS, FCAP)
        keys = tuple(jnp.where(elive, fp[w], jnp.uint32(0xFFFFFFFF))
                     for w in range(eng.W))
        ranks = jnp.arange(FCAP, dtype=jnp.uint32)
        table, claims, fresh, pos, hv = eng._probe_insert(
            carry["vis"], carry["claims"], keys, elive, ranks)
        return fresh.sum() + table[0].astype(jnp.int32).sum()

    @jax.jit
    def expand_fp_phase2(carry, base):
        sv, valid = chunk_front(carry, base)
        cand_c, elive, fp, take, famx, n_e = eng._expand_fp_chunk(
            sv, valid, eng.FAM_CAPS, FCAP)
        inv, con = eng._phase2_T(cand_c)
        return inv.sum() + con.sum()

    n_chunks = max(1, n_front // B)
    iters = min(10, max(2, n_chunks))

    def comp(fn):
        return lambda i: fn(carry, jnp.int32((i % n_chunks) * B))

    t_g = _bench(rec, "guard_pass", comp(guard_only), iters)
    t_e = _bench(rec, "expand_materialize_fp", comp(expand_fp), iters)
    t_p = _bench(rec, "probe_insert_dedup", comp(expand_fp_probe),
                 iters)
    t_2 = _bench(rec, "phase2_predicates", comp(expand_fp_phase2),
                 iters)

    # full fused step: donated carry — run on a copy stream
    c2 = jax.tree_util.tree_map(jnp.asarray, snap["c"])
    with rec.span("compile"):
        c2 = eng._step_jit(c2, eng.FAM_CAPS)
        _ = int(np.asarray(c2["n_lvl"]))
    t0 = time.perf_counter()
    for _ in range(iters):
        with rec.span("level_dispatch"):
            c2 = eng._step_jit(c2, eng.FAM_CAPS)
    _ = int(np.asarray(c2["n_lvl"]))
    dt = (time.perf_counter() - t0) / iters
    print(f"{'FULL fused step':30s} steady {dt * 1000:8.2f} ms/chunk"
          f"   {B / dt:9.0f} parents/s", flush=True)
    print(f"attribution (ms/chunk): guard={t_g * 1000:.1f}  "
          f"mat+fp={1000 * (t_e - t_g):.1f}  "
          f"probe={1000 * (t_p - t_e):.1f}  "
          f"phase2={1000 * (t_2 - t_e):.1f}  "
          f"append+rest={1000 * (dt - t_p - (t_2 - t_e)):.1f}",
          flush=True)


def main():
    args = sys.argv[1:]
    if not args or args[0] in ("-h", "--help"):
        print(__doc__)
        return 0 if args else 2
    mode = args.pop(0)
    opts = dict(zip(args[::2], args[1::2]))
    known = {"--config", "--chunk", "--depth", "--lcap", "--vcap",
             "--budget", "--timeline"}
    bad = set(opts) - known
    if bad or len(args) % 2 or mode not in ("steady", "phases"):
        raise SystemExit(
            f"usage: profile.py steady|phases [opts]; unknown: "
            f"{sorted(bad) or [mode]} (known: {sorted(known)})")
    rec = SpanRecorder(opts.get("--timeline"))
    try:
        (mode_steady if mode == "steady" else mode_phases)(opts, rec)
    finally:
        rec.close()
    # the ONE span-rollup rendering lives in obs/report.py (ISSUE 17);
    # `cli obs show/diff` print the same shape
    from raft_tla_tpu.obs.report import format_span_totals
    print("span totals: " + format_span_totals(rec.totals()),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
