"""Phase-level profile of config #3 (membership, S=4, NextDynamic) —
the 0.32x single-chip gap (VERDICT r4 #1).

Captures a realistic mid-depth frontier (monkeypatched finalize hook),
then times the fused chunk step and its subcomponents separately:
guard pass, guard+materialize+fingerprint (_expand_fp_chunk), and the
full step (adds probe-insert dedup + phase2 + level append).  The
differences attribute the per-chunk wall to phases.

Usage: python tools/profile_config3.py [depth_to_capture] [chunk]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax

from tools.measure_baseline import build_cfg, ENGINE_KW
from raft_tla_tpu.engine.bfs import Engine


def main():
    cap_depth = int(sys.argv[1]) if len(sys.argv) > 1 else 13
    kw = dict(ENGINE_KW[3])
    if len(sys.argv) > 2:
        kw["chunk"] = int(sys.argv[2])
    cfg = build_cfg(3)
    eng = Engine(cfg, store_states=False, **kw)
    print(f"lanes={eng.A} chunk={eng.chunk} FCAP={eng.FCAP} "
          f"fam_caps={dict(zip([f.name for f in eng.expander.families], eng.FAM_CAPS))}",
          flush=True)

    # ---- capture the carry as it enters the finalize at cap_depth ----
    snap = {}
    real_fin = eng._fin_jit
    lvl = [0]

    def fin_hook(carry):
        lvl[0] += 1
        if lvl[0] == cap_depth and "front" not in snap:
            # snapshot to host BEFORE donation invalidates the buffers
            snap["carry"] = jax.tree_util.tree_map(np.asarray, carry)
        return real_fin(carry)

    eng._fin_jit = fin_hook
    t0 = time.time()
    r = eng.check(max_depth=cap_depth, max_states=1_500_000)
    print(f"capture run: {r.distinct_states} states depth {r.depth} "
          f"in {time.time()-t0:.1f}s ({r.states_per_sec:.0f}/s)", flush=True)
    eng._fin_jit = real_fin
    carry_h = snap["carry"]
    # re-finalize the captured carry on device to get a fresh frontier
    carry = jax.tree_util.tree_map(jnp.asarray, carry_h)
    carry, out = eng._fin_jit(carry)
    scal = [int(x) for x in np.asarray(out["scal"])]
    n_front = scal[3]
    print(f"captured frontier: {n_front} rows at depth {cap_depth}", flush=True)

    B, A, FCAP = eng.chunk, eng.A, eng.FCAP
    from raft_tla_tpu.ops.codec import widen

    def chunk_front(carry, base):
        sv = widen({k: lax.dynamic_slice_in_dim(v, base, B, axis=v.ndim - 1)
                    for k, v in carry["front"].items()})
        fmask = lax.dynamic_slice_in_dim(carry["fmask"], base, B)
        valid = ((base + jnp.arange(B, dtype=jnp.int32)) <
                 carry["n_front"]) & fmask
        return sv, valid

    # ---- component jits ----
    @jax.jit
    def guard_only(carry, base):
        sv, valid = chunk_front(carry, base)
        derb = eng.expander.derived_batch_T(sv)
        ok = eng.expander.guards_T(sv, derb)
        return (ok & valid[:, None]).sum()

    @jax.jit
    def expand_fp(carry, base):
        sv, valid = chunk_front(carry, base)
        cand_c, elive, fp, take, famx, n_e = eng._expand_fp_chunk(
            sv, valid, eng.FAM_CAPS, FCAP)
        # consume everything so nothing is DCE'd
        s = sum(jnp.sum(v.astype(jnp.int32)) for v in cand_c.values())
        return s + fp.astype(jnp.int32).sum() + n_e + elive.sum()

    @jax.jit
    def expand_fp_nophase2_probe(carry, base):
        # expand+fp+probe-insert but no phase2/append: isolates dedup
        sv, valid = chunk_front(carry, base)
        cand_c, elive, fp, take, famx, n_e = eng._expand_fp_chunk(
            sv, valid, eng.FAM_CAPS, FCAP)
        W = eng.W
        keys = tuple(jnp.where(elive, fp[w], jnp.uint32(0xFFFFFFFF))
                     for w in range(W))
        ranks = jnp.arange(FCAP, dtype=jnp.uint32)
        table, claims, fresh, pos, hv = eng._probe_insert(
            carry["vis"], carry["claims"], keys, elive, ranks)
        return fresh.sum() + table[0].astype(jnp.int32).sum()

    @jax.jit
    def phase2_only(carry, base):
        sv, valid = chunk_front(carry, base)
        cand_c, elive, fp, take, famx, n_e = eng._expand_fp_chunk(
            sv, valid, eng.FAM_CAPS, FCAP)
        inv, con = eng._phase2_T(cand_c)
        return inv.sum() + con.sum()

    n_chunks_avail = n_front // B
    iters = min(10, max(2, n_chunks_avail))

    def bench(name, fn, needs_fresh_carry=False):
        # warm/compile
        t0 = time.time()
        v = fn(carry, jnp.int32(0))
        v.block_until_ready()
        tc = time.time() - t0
        t0 = time.time()
        for i in range(iters):
            v = fn(carry, jnp.int32((i % max(1, n_chunks_avail)) * B))
        np.asarray(v)
        dt = (time.time() - t0) / iters
        print(f"{name:28s} compile {tc:6.1f}s   steady {dt*1000:8.2f} ms/chunk"
              f"   {B/dt:9.0f} parents/s", flush=True)
        return dt

    t_g = bench("guard pass", guard_only)
    t_e = bench("expand+materialize+fp", expand_fp)
    t_p = bench("  + probe-insert", expand_fp_nophase2_probe)
    t_2 = bench("  + phase2 (no probe)", phase2_only)

    # full fused step: donated carry — run on a copy stream
    t0 = time.time()
    c2 = eng._step_jit(jax.tree_util.tree_map(jnp.asarray, carry_h), eng.FAM_CAPS)
    _ = int(np.asarray(c2["n_lvl"]))
    tc = time.time() - t0
    t0 = time.time()
    for _ in range(iters):
        c2 = eng._step_jit(c2, eng.FAM_CAPS)
    _ = int(np.asarray(c2["n_lvl"]))
    dt = (time.time() - t0) / iters
    print(f"{'FULL fused step':28s} compile {tc:6.1f}s   steady {dt*1000:8.2f} ms/chunk"
          f"   {eng.chunk/dt:9.0f} parents/s", flush=True)
    print(f"attribution: guard={t_g*1000:.1f}  mat+fp={1000*(t_e-t_g):.1f}  "
          f"probe={1000*(t_p-t_e):.1f}  phase2={1000*(t_2-t_e):.1f}  "
          f"append+rest={1000*(dt-t_p-(t_2-t_e)):.1f}  (ms)", flush=True)


if __name__ == "__main__":
    main()
