"""Finer phase attribution for config #3 (follow-up to
profile_config3.py).  Caches the captured frontier to disk so component
experiments iterate without re-running the capture BFS.

Components timed:
  derived_batch_T alone
  guard pass: all families / message families only / others only
  materialize without fp
  materialize + incremental fp (production path)
  materialize + direct fingerprint_batch_T
  phase2 at FCAP width vs chunk*4 width
  append path (gather FCAP rows + narrow + DUS) vs chunk*4 width

Usage: python tools/profile_config3b.py [depth_to_capture] [chunk]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax

from tools.measure_baseline import build_cfg, ENGINE_KW
from raft_tla_tpu.engine.bfs import Engine
from raft_tla_tpu.ops.codec import widen, narrow

CACHE = "/tmp/cfg3_frontier.npz"


def capture(eng, cap_depth):
    if os.path.exists(CACHE):
        z = np.load(CACHE)
        if int(z["chunk"]) == eng.chunk:
            carry_h = {}
            front = {}
            for k in z.files:
                if k.startswith("front|"):
                    front[k.split("|", 1)[1]] = z[k]
            return front, z["fmask"], int(z["n_front"])
    snap = {}
    real_fin = eng._fin_jit
    lvl = [0]

    def fin_hook(carry):
        lvl[0] += 1
        if lvl[0] == cap_depth and "c" not in snap:
            snap["c"] = jax.tree_util.tree_map(np.asarray, carry)
        return real_fin(carry)

    eng._fin_jit = fin_hook
    r = eng.check(max_depth=cap_depth, max_states=1_500_000)
    eng._fin_jit = real_fin
    carry = jax.tree_util.tree_map(jnp.asarray, snap["c"])
    carry, out = eng._fin_jit(carry)
    scal = [int(x) for x in np.asarray(out["scal"])]
    n_front = scal[3]
    front = {k: np.asarray(v) for k, v in carry["front"].items()}
    fmask = np.asarray(carry["fmask"])
    np.savez(CACHE, chunk=eng.chunk, n_front=n_front, fmask=fmask,
             **{f"front|{k}": v for k, v in front.items()})
    print(f"captured frontier: {n_front} rows at depth {cap_depth}",
          flush=True)
    return front, fmask, n_front


def main():
    cap_depth = int(sys.argv[1]) if len(sys.argv) > 1 else 13
    kw = dict(ENGINE_KW[3])
    if len(sys.argv) > 2:
        kw["chunk"] = int(sys.argv[2])
    cfg = build_cfg(3)
    eng = Engine(cfg, store_states=False, **kw)
    fams = eng.expander.families
    print(f"lanes={eng.A} chunk={eng.chunk} FCAP={eng.FCAP} "
          f"W={eng.W} fam_lanes={[(f.name, f.n_lanes) for f in fams]}",
          flush=True)
    front_h, fmask_h, n_front = capture(eng, cap_depth)

    B, A, FCAP = eng.chunk, eng.A, eng.FCAP
    # one chunk of real frontier rows, device-resident, batch-last
    sv_h = {k: v[..., :B] for k, v in front_h.items()}
    sv = widen({k: jnp.asarray(v) for k, v in sv_h.items()})
    valid = jnp.asarray(fmask_h[:B] & (np.arange(B) < n_front))
    iters = 10

    def bench(name, fn, *args):
        t0 = time.time()
        v = jax.block_until_ready(fn(*args))
        tc = time.time() - t0
        t0 = time.time()
        for _ in range(iters):
            v = fn(*args)
        jax.tree_util.tree_map(
            lambda x: np.asarray(x) if hasattr(x, "shape") else x, v)
        dt = (time.time() - t0) / iters
        print(f"{name:34s} compile {tc:6.1f}s   steady {dt*1000:8.2f} ms",
              flush=True)
        return dt

    exp = eng.expander
    MSG = {"UpdateTerm", "CocDiscard", "Receive", "Duplicate", "Drop"}

    @jax.jit
    def derived_only(sv):
        d = exp.derived_batch_T(sv)
        return sum(jnp.sum(v.astype(jnp.int32)) for v in d.values())

    def guard_subset(which):
        @jax.jit
        def g(sv):
            derb = exp.derived_batch_T(sv)

            def one(svx, derx):
                oks = []
                for fam in fams:
                    if which != "all" and \
                            ((fam.name in MSG) != (which == "msg")):
                        continue
                    lane = jax.vmap(
                        fam.fn,
                        in_axes=(None, None) + (0,) * len(fam.params))
                    ok, _ = lane(svx, derx,
                                 *[jnp.asarray(p) for p in fam.params])
                    oks.append(ok.reshape(-1))
                return jnp.concatenate(oks)
            ok = jax.vmap(one, in_axes=-1, out_axes=-1)(sv, derb)
            return ok.sum()
        return g

    # materialize variants need okf/epos: build from a real guard pass
    @jax.jit
    def guard_pack(sv, valid):
        derb = exp.derived_batch_T(sv)
        ok = exp.guards_T(sv, derb)
        okf = (ok & valid[:, None]).reshape(B * A)
        epos = jnp.where(okf, jnp.cumsum(okf.astype(jnp.int32)) - 1, FCAP)
        return derb, okf, epos

    derb, okf, epos = jax.block_until_ready(guard_pack(sv, valid))
    print(f"enabled lanes in this chunk: {int(np.asarray(okf.sum()))} "
          f"(of {B*A})", flush=True)

    @jax.jit
    def mat_only(sv, derb, okf, epos):
        cand, counts = exp.materialize(sv, derb, okf, epos, FCAP,
                                       eng.FAM_CAPS)
        return sum(jnp.sum(v.astype(jnp.int32)) for v in cand.values())

    @jax.jit
    def mat_incr(sv, derb, okf, epos):
        tables = eng.fpr.parent_tables(sv)
        cand, counts, fp = exp.materialize(
            sv, derb, okf, epos, FCAP, eng.FAM_CAPS,
            delta_fp=(eng.fpr, tables))
        return sum(jnp.sum(v.astype(jnp.int32)) for v in cand.values()) \
            + fp.astype(jnp.int32).sum()

    @jax.jit
    def mat_direct(sv, derb, okf, epos):
        cand, counts = exp.materialize(sv, derb, okf, epos, FCAP,
                                       eng.FAM_CAPS)
        fp = eng.fpr.fingerprint_batch_T(cand)
        return sum(jnp.sum(v.astype(jnp.int32)) for v in cand.values()) \
            + fp.astype(jnp.int32).sum()

    # phase2 / append width experiments on synthetic candidate buffers
    cand_h = jax.block_until_ready(jax.jit(
        lambda sv, derb, okf, epos: exp.materialize(
            sv, derb, okf, epos, FCAP, eng.FAM_CAPS)[0])(
            sv, derb, okf, epos))

    def phase2_w(width):
        rows = {k: v[..., :width] for k, v in cand_h.items()}
        rows = jax.tree_util.tree_map(jnp.asarray, rows)

        @jax.jit
        def p2(rows):
            inv, con = eng._phase2_T(rows)
            return inv.sum() + con.sum()
        return p2, rows

    LCAP = eng.LCAP

    def append_w(width):
        rows = {k: jnp.asarray(v[..., :width])
                for k, v in cand_h.items()}
        lvl = {k: jnp.zeros(v.shape[:-1] + (LCAP,),
                            narrow(eng.lay, {k: v[..., :1]})[k].dtype)
               for k, v in rows.items()}

        @jax.jit
        def ap(rows, lvl, lidx, start):
            g = {k: v[..., lidx] for k, v in rows.items()}
            g = narrow(eng.lay, g)
            out = {k: lax.dynamic_update_slice_in_dim(
                lvl[k], g[k], start, lvl[k].ndim - 1) for k in lvl}
            return sum(jnp.sum(v.astype(jnp.int32)) for v in out.values())
        lidx = jnp.arange(width, dtype=jnp.int32)
        return ap, rows, lvl, lidx

    bench("derived_batch_T", derived_only, sv)
    bench("guard msg families (95 lanes)", guard_subset("msg"), sv)
    bench("guard other families", guard_subset("oth"), sv)
    bench("guard all", guard_subset("all"), sv)
    bench("materialize only", mat_only, sv, derb, okf, epos)
    bench("materialize + incr fp", mat_incr, sv, derb, okf, epos)
    bench("materialize + direct fp", mat_direct, sv, derb, okf, epos)
    p2f, p2rows = phase2_w(FCAP)
    bench(f"phase2 @ {FCAP}", p2f, p2rows)
    p2f, p2rows = phase2_w(4 * B)
    bench(f"phase2 @ {4*B}", p2f, p2rows)
    apf, rows, lvl, lidx = append_w(FCAP)
    bench(f"append @ {FCAP}", apf, rows, lvl, lidx, jnp.int32(0))
    apf, rows, lvl, lidx = append_w(4 * B)
    bench(f"append @ {4*B}", apf, rows, lvl, lidx, jnp.int32(0))


if __name__ == "__main__":
    main()
