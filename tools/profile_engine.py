"""Profile the TPU engine on the bench config: where does wall time go?

Times jit compilation vs steady-state chunk steps vs finalize, and counts
recompiles caused by LCAP/VCAP growth.
"""
import sys
import time
import os, sys; sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_tla_tpu.cfg.parser import load_model
from raft_tla_tpu.config import Bounds
from raft_tla_tpu.engine.bfs import Engine

import jax
import jax.numpy as jnp

cfg = load_model("/root/reference/tlc_membership/raft.cfg",
                 bounds=Bounds.make(max_log_length=3, max_timeouts=2,
                                    max_client_requests=3))
cfg = cfg.with_(invariants=("ElectionSafety",))

chunk = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
lcap = int(sys.argv[2]) if len(sys.argv) > 2 else 1 << 14
vcap = int(sys.argv[3]) if len(sys.argv) > 3 else 1 << 17
eng = Engine(cfg, chunk=chunk, store_states=False, lcap=lcap, vcap=vcap)
print(f"lanes={eng.A} chunk={chunk} N={chunk*eng.A} lcap={lcap} vcap={vcap}")

# --- compile timings -------------------------------------------------
carry = eng._fresh_carry(eng.LCAP, eng.VCAP)
t0 = time.time(); c2 = eng._step_jit(carry, eng.FAM_CAPS)
jax.block_until_ready(c2["n_lvl"]); print(f"step compile+run1: {time.time()-t0:.1f}s")
t0 = time.time(); c3, out = eng._fin_jit(c2)
jax.block_until_ready(out["scal"]); print(f"finalize compile+run1: {time.time()-t0:.1f}s")

# steady state: time 10 chunk steps + 1 finalize (block_until_ready is
# unreliable through the axon tunnel: sync with a real transfer)
import numpy as _np
t0 = time.time()
for _ in range(10):
    c3 = eng._step_jit(c3, eng.FAM_CAPS)
_ = int(_np.asarray(c3["n_lvl"]))
dt = (time.time()-t0)/10
print(f"steady chunk step: {dt*1000:.1f} ms -> {chunk/dt:.0f} parent-states/s "
      f"({chunk*eng.A/dt:.0f} cand/s)")
t0 = time.time(); c4, out = eng._fin_jit(c3)
_ = _np.asarray(out["scal"])
print(f"steady finalize: {(time.time()-t0)*1000:.1f} ms")

# --- full run with growth logging -----------------------------------
eng2 = Engine(cfg, chunk=chunk, store_states=False, lcap=lcap, vcap=vcap)
t0 = time.time()
r = eng2.check(max_states=150_000, verbose=True)
print(f"full: {r.distinct_states} states depth {r.depth} in "
      f"{time.time()-t0:.1f}s -> {r.states_per_sec:.0f}/s  "
      f"final LCAP={eng2.LCAP} VCAP={eng2.VCAP}")
