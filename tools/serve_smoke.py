"""CI batch-serving smoke: `cli batch` end-to-end, then the cache.

Two tiny jobs (one raft, one paxos — the paxos one through the TLC
.cfg front-end) run through ``python -m raft_tla_tpu batch`` with a
result cache and a ledger; a second invocation of the SAME job list
must then be served entirely from the fingerprint-keyed cache: every
job row says cache_hit, the summary reports zero batched dispatches
and zero engines compiled, and the re-run's ledger contains NO device
dispatch records of any kind (kind=batch/burst/level) — only the
kind=job completion rows.  Exercises: JSONL parsing, bucketing, the
job-vmapped burst, report assembly, ResultCache round-trip, and the
obs threading (ledger + heartbeat incl. the per-job map).
"""

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PAXOS_CFG = """\\* tiny paxos model (batch smoke)
CONSTANTS
  a1 = 1
  a2 = 2
  Acceptor = {a1, a2}
  Ballot = {0}
  Value = {0}
INIT Init
NEXT Next
INVARIANT Agreement
"""


def run_batch(jobs_path, cache_dir, ledger, heartbeat):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "raft_tla_tpu", "batch",
         "--jobs", jobs_path, "--cache-dir", cache_dir,
         "--ledger", ledger, "--heartbeat", heartbeat],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600)
    assert p.returncode == 0, (p.returncode, p.stdout, p.stderr)
    lines = [json.loads(ln) for ln in p.stdout.splitlines() if ln]
    summary, rows = lines[0], lines[1:]
    assert summary["kind"] == "batch_summary", summary
    return summary, rows


def ledger_kinds(path):
    kinds = []
    with open(path) as fh:
        for line in fh:
            kinds.append(json.loads(line).get("kind"))
    return kinds


def main():
    tmp = tempfile.mkdtemp(prefix="serve_smoke_")
    pax_cfg = os.path.join(tmp, "paxos.cfg")
    with open(pax_cfg, "w") as fh:
        fh.write(PAXOS_CFG)
    jobs = [
        {"spec": "raft", "config": "configs/tlc_membership/raft.cfg",
         "overrides": {"servers": 2, "values": [1], "max_inflight": 4,
                       "next": "NextAsync",
                       "bounds": {"max_log_length": 1,
                                  "max_timeouts": 1,
                                  "max_client_requests": 1}},
         "max_depth": 3, "label": "raft-micro"},
        {"spec": "paxos", "config": pax_cfg, "max_depth": 3,
         "label": "paxos-micro"},
    ]
    jobs_path = os.path.join(tmp, "jobs.jsonl")
    with open(jobs_path, "w") as fh:
        for obj in jobs:
            fh.write(json.dumps(obj) + "\n")
    cache = os.path.join(tmp, "cache")
    hb = os.path.join(tmp, "hb.json")

    # run 1: cold — both jobs computed, batched, one bucket per spec
    s1, rows1 = run_batch(jobs_path, cache, os.path.join(tmp, "l1"),
                          hb)
    assert s1["jobs"] == 2 and s1["cache_hits"] == 0, s1
    assert s1["buckets"] == 2 and s1["batch_dispatches"] >= 2, s1
    assert all(r["status"] == "done" for r in rows1), rows1
    k1 = ledger_kinds(os.path.join(tmp, "l1"))
    assert "batch" in k1 and k1.count("job") == 2, k1
    with open(hb) as fh:
        hb1 = json.load(fh)
    assert set(hb1.get("jobs", {})) == {"raft-micro", "paxos-micro"}, \
        hb1

    # run 2: identical list — served ENTIRELY from the result cache,
    # zero device dispatches in the ledger
    s2, rows2 = run_batch(jobs_path, cache, os.path.join(tmp, "l2"),
                          hb)
    assert s2["cache_hits"] == 2, s2
    assert s2["batch_dispatches"] == 0 and \
        s2["engines_compiled"] == 0, s2
    assert all(r["status"] == "cache_hit" for r in rows2), rows2
    for a, b in zip(rows1, rows2):
        assert a["distinct_states"] == b["distinct_states"] and \
            a["level_sizes"] == b["level_sizes"], (a, b)
    k2 = ledger_kinds(os.path.join(tmp, "l2"))
    assert set(k2) == {"job"}, \
        f"cached re-run must dispatch nothing, ledger kinds: {k2}"
    print("serve_smoke: OK (2 jobs batched; re-run 100% cache, "
          "0 device dispatches)")


if __name__ == "__main__":
    main()
