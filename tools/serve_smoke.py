"""CI batch-serving smoke: `cli batch` end-to-end, then the cache.

Two tiny jobs (one raft, one paxos — the paxos one through the TLC
.cfg front-end) run through ``python -m raft_tla_tpu batch`` with a
result cache and a ledger; a second invocation of the SAME job list
must then be served entirely from the fingerprint-keyed cache: every
job row says cache_hit, the summary reports zero batched dispatches
and zero engines compiled, and the re-run's ledger contains NO device
dispatch records of any kind (kind=batch/burst/level) — only the
kind=job completion rows.  Exercises: JSONL parsing, bucketing, the
job-vmapped burst, report assembly, ResultCache round-trip, and the
obs threading (ledger + heartbeat incl. the per-job map).

Round 13 adds two steps:

- **heterogeneous-constants wave** — K=4 raft jobs with DISTINCT
  value bounds (max_timeouts × max_log_length) land in ONE padded
  bucket ceiling and compile ONCE: the summary reports buckets=1 /
  engines_compiled=1 and the span timeline holds exactly one
  ``bucket_compile`` event (bit-exactness vs solo engines is pinned
  by tests/test_serve.py; this smoke pins the CLI-level
  compile-amortization contract every run);
- **executable-cache warm rerun** — the same wave re-runs with a
  fresh result cache but a warm ``--executable-cache``: zero
  ``bucket_compile`` spans, every executable loaded from disk.  On a
  backend whose runtime cannot serialize executables the step SKIPS
  with the named store-failure reason (the honest-miss contract) —
  never a crash.
"""

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PAXOS_CFG = """\\* tiny paxos model (batch smoke)
CONSTANTS
  a1 = 1
  a2 = 2
  Acceptor = {a1, a2}
  Ballot = {0}
  Value = {0}
INIT Init
NEXT Next
INVARIANT Agreement
"""


def run_batch(jobs_path, cache_dir, ledger, heartbeat, extra=()):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "raft_tla_tpu", "batch",
         "--jobs", jobs_path, "--cache-dir", cache_dir,
         "--ledger", ledger, "--heartbeat", heartbeat, *extra],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600)
    assert p.returncode == 0, (p.returncode, p.stdout, p.stderr)
    lines = [json.loads(ln) for ln in p.stdout.splitlines() if ln]
    summary, rows = lines[0], lines[1:]
    assert summary["kind"] == "batch_summary", summary
    return summary, rows


def span_count(timeline_path, name):
    """Occurrences of a span name in a Chrome-trace timeline file."""
    with open(timeline_path) as fh:
        return fh.read().count(f'"name": "{name}"')


def ledger_kinds(path):
    kinds = []
    with open(path) as fh:
        for line in fh:
            kinds.append(json.loads(line).get("kind"))
    return kinds


def main():
    tmp = tempfile.mkdtemp(prefix="serve_smoke_")
    pax_cfg = os.path.join(tmp, "paxos.cfg")
    with open(pax_cfg, "w") as fh:
        fh.write(PAXOS_CFG)
    jobs = [
        {"spec": "raft", "config": "configs/tlc_membership/raft.cfg",
         "overrides": {"servers": 2, "values": [1], "max_inflight": 4,
                       "next": "NextAsync",
                       "bounds": {"max_log_length": 1,
                                  "max_timeouts": 1,
                                  "max_client_requests": 1}},
         "max_depth": 3, "label": "raft-micro"},
        {"spec": "paxos", "config": pax_cfg, "max_depth": 3,
         "label": "paxos-micro"},
    ]
    jobs_path = os.path.join(tmp, "jobs.jsonl")
    with open(jobs_path, "w") as fh:
        for obj in jobs:
            fh.write(json.dumps(obj) + "\n")
    cache = os.path.join(tmp, "cache")
    hb = os.path.join(tmp, "hb.json")

    # run 1: cold — both jobs computed, batched, one bucket per spec
    s1, rows1 = run_batch(jobs_path, cache, os.path.join(tmp, "l1"),
                          hb)
    assert s1["jobs"] == 2 and s1["cache_hits"] == 0, s1
    assert s1["buckets"] == 2 and s1["batch_dispatches"] >= 2, s1
    assert all(r["status"] == "done" for r in rows1), rows1
    k1 = ledger_kinds(os.path.join(tmp, "l1"))
    assert "batch" in k1 and k1.count("job") == 2, k1
    with open(hb) as fh:
        hb1 = json.load(fh)
    assert set(hb1.get("jobs", {})) == {"raft-micro", "paxos-micro"}, \
        hb1

    # run 2: identical list — served ENTIRELY from the result cache,
    # zero device dispatches in the ledger
    s2, rows2 = run_batch(jobs_path, cache, os.path.join(tmp, "l2"),
                          hb)
    assert s2["cache_hits"] == 2, s2
    assert s2["batch_dispatches"] == 0 and \
        s2["engines_compiled"] == 0, s2
    assert all(r["status"] == "cache_hit" for r in rows2), rows2
    for a, b in zip(rows1, rows2):
        assert a["distinct_states"] == b["distinct_states"] and \
            a["level_sizes"] == b["level_sizes"], (a, b)
    k2 = ledger_kinds(os.path.join(tmp, "l2"))
    # meta (run start) and resource (sampler) rows are bookkeeping,
    # not dispatches — the contract is zero DEVICE dispatch kinds
    assert set(k2) - {"tenant", "meta", "resource"} == {"job"}, \
        f"cached re-run must dispatch nothing, ledger kinds: {k2}"
    print("serve_smoke: OK (2 jobs batched; re-run 100% cache, "
          "0 device dispatches)")

    # step 3: heterogeneous-constants wave — 4 raft jobs with distinct
    # bounds share ONE padded bucket ceiling and compile ONCE
    het = []
    for k, (mt, mll) in enumerate(((1, 1), (2, 1), (1, 2), (2, 2))):
        het.append({
            "spec": "raft",
            "config": "configs/tlc_membership/raft.cfg",
            "overrides": {"servers": 2, "values": [1],
                          "max_inflight": 4, "next": "NextAsync",
                          "bounds": {"max_log_length": mll,
                                     "max_timeouts": mt,
                                     "max_client_requests": 1}},
            "max_depth": 4, "label": f"het{k}"})
    het_path = os.path.join(tmp, "het.jsonl")
    with open(het_path, "w") as fh:
        for obj in het:
            fh.write(json.dumps(obj) + "\n")
    tl3 = os.path.join(tmp, "tl3.json")
    exec_dir = os.path.join(tmp, "exec")
    s3, rows3 = run_batch(
        het_path, os.path.join(tmp, "cache3"),
        os.path.join(tmp, "l3"), hb,
        extra=("--trace-timeline", tl3,
               "--executable-cache", exec_dir))
    assert s3["buckets"] == 1 and s3["engines_compiled"] == 1, s3
    assert s3["fallback_jobs"] == 0, s3
    assert all(r["status"] == "done" for r in rows3), rows3
    ncomp = span_count(tl3, "bucket_compile")
    assert ncomp == 1, \
        f"heterogeneous wave must compile ONCE, saw {ncomp} spans"
    with open(hb) as fh:
        hb3 = json.load(fh)
    assert "slo" in hb3 and "service_hist" in hb3["slo"], hb3

    # step 4: executable-cache warm rerun — fresh RESULT cache (so the
    # wave really re-runs) but a warm exec cache: zero compiles
    if s3.get("exec_cache_store_failures"):
        why = (s3.get("exec_cache_store_fail_reasons") or ["?"])[-1]
        print(f"serve_smoke: heterogeneous wave OK (1 bucket_compile "
              f"span); SKIPPING warm-rerun step — backend cannot "
              f"serialize executables: {why}")
        return
    tl4 = os.path.join(tmp, "tl4.json")
    s4, rows4 = run_batch(
        het_path, os.path.join(tmp, "cache4"),
        os.path.join(tmp, "l4"), hb,
        extra=("--trace-timeline", tl4,
               "--executable-cache", exec_dir))
    assert s4.get("exec_cache_hits", 0) >= 1, s4
    ncomp4 = span_count(tl4, "bucket_compile")
    assert ncomp4 == 0, \
        f"warm exec-cache rerun must compile NOTHING, saw {ncomp4}"
    for a, b in zip(rows3, rows4):
        assert a["distinct_states"] == b["distinct_states"] and \
            a["level_sizes"] == b["level_sizes"], (a, b)
    print("serve_smoke: OK (heterogeneous wave: 4 jobs, 1 compile; "
          "warm exec-cache rerun: 0 compiles)")


if __name__ == "__main__":
    main()
