"""Orbit-sort canonicalization CI smoke (tools/ci_smoke.sh, round 15).

Depth-capped CLI checks with ``--sym-canon sort`` (ONE argsorted
canonical relabeling hashed per state) vs ``--sym-canon minperm``
(the P-fold min-over-perms) must land on IDENTICAL counts — for a
symmetric raft config whose perm group has the inside/outside block
structure AND for the stock paxos model (full S_N, owned-bit affine
salt map).  Exercises the end-to-end flag wiring (CLI → engine →
Fingerprinter) plus the stats mode flag (sym_canon 1/0).

Sub-minute on CPU; the full-space duplicates and the oracle-partition
parity live in tests/test_sym_canon.py.  Exits 0 on identity, 1 with
a message on any divergence.
"""

import json
import os
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fail(msg):
    print(f"sym_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def run_one(spec_args, mode, stats_path):
    cmd = [sys.executable, "-m", "raft_tla_tpu", "check"] + \
        spec_args + ["--sym-canon", mode, "--stats-json", stats_path]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(cmd, env=env, cwd=_REPO,
                          capture_output=True, text=True)
    if proc.returncode != 0:
        fail(f"check {' '.join(spec_args[:1])} --sym-canon {mode} "
             f"failed rc={proc.returncode}:\n{proc.stderr}")
    with open(stats_path) as fh:
        return json.load(fh)


def ab(name, spec_args, td):
    srt = run_one(spec_args, "sort",
                  os.path.join(td, f"{name}_sort.json"))
    mnp = run_one(spec_args, "minperm",
                  os.path.join(td, f"{name}_minperm.json"))
    if srt.get("sym_canon") != 1 or mnp.get("sym_canon") != 0:
        fail(f"{name}: mode flags wrong: sort={srt.get('sym_canon')} "
             f"minperm={mnp.get('sym_canon')} — the CLI flag did not "
             "reach the engine")
    for key in ("distinct_states", "generated_states", "depth",
                "dedup_hit_rate", "violations"):
        if srt[key] != mnp[key]:
            fail(f"{name} {key}: sort {srt[key]} != minperm "
                 f"{mnp[key]} — the orbit partitions diverged")
    print(f"sym_smoke: {name} sort ≡ minperm at depth {srt['depth']} "
          f"({srt['distinct_states']} orbits)")


def main():
    with tempfile.TemporaryDirectory(prefix="sym_smoke_") as td:
        # S=3 ⊋ init=2: the block-product perm group (P=2), forced
        # sort — auto would pick minperm at this size, and the smoke
        # must pin the sort program itself
        ab("raft", [
            os.path.join(_REPO, "configs", "tlc_membership",
                         "raft.cfg"),
            "--servers", "3", "--init-servers", "2", "--symmetry",
            "--max-log-length", "1", "--max-timeouts", "1",
            "--max-client-requests", "1", "--max-depth", "6"], td)
        ab("paxos", ["--spec", "paxos", "--max-depth", "6"], td)
    print("sym_smoke: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
