"""Real-TLC baseline harness (VERDICT r3 #9; BASELINE.md).

The repo's 50x target (BASELINE.json) names **TLC -workers N** as the
comparison point, but this image has no Java, so every recorded
baseline uses the in-repo native C++ checker as a stand-in.  This tool
closes the loop for any Java-equipped host:

  1. ``emit_tlc_model(cfg, out_dir)`` materializes a TLC-ready model
     directory from a ``ModelConfig``: the reference spec with its
     in-spec bound constants rewritten to the config's Bounds (the
     reference requires editing the spec for those — SURVEY §5 config
     tier b), the vendored library modules, and a generated ``raft.cfg``
     binding CONSTANTS / NEXT / CONSTRAINTS / INVARIANTS exactly as the
     engine runs them.
  2. ``run_tlc(model_dir, ...)`` invokes ``java tlc2.TLC -workers N``
     and parses distinct states / diameter / wall seconds.
  3. ``main`` compares the TLC counts+rate against the engine/oracle
     and prints one JSON line — the actual number the 50x target names.

Where Java or tla2tools.jar is absent (this image), the tool prints a
skip record and exits 0.  Locate the jar via ``--tla2tools`` or the
``TLA2TOOLS_JAR`` env var.

The emitted spec is a *runtime transformation of the user's local
reference checkout* (bounds substituted); nothing is vendored into
this repo.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REFERENCE = os.environ.get("RAFT_TLA_REFERENCE",
                           "/root/reference/tlc_membership")
if not os.path.isdir(REFERENCE):
    # containers without the reference checkout: the repo-local cfg
    # twin still lets --cfg default/parse work (emit of a full model
    # dir additionally needs the real spec + vendored libraries and
    # will fail loudly if attempted against the stub)
    _local = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "configs", "tlc_membership")
    if os.path.isdir(_local):
        REFERENCE = _local

# in-spec bound constants (tlc_membership/raft.tla:22-30) -> Bounds field
_BOUND_LINES = {
    "MaxLogLength": "max_log_length",
    "MaxRestarts": "max_restarts",
    "MaxTimeouts": "max_timeouts",
    "MaxClientRequests": "max_client_requests",
    "MaxTerms": "max_terms",
    "MaxMembershipChanges": "max_membership_changes",
    "MaxTriedMembershipChanges": "max_tried_membership_changes",
}

_LIB_MODULES = ("TypedBags.tla", "SequencesExt.tla",
                "FiniteSetsExt.tla", "Functions.tla")


def emit_tlc_model(cfg, out_dir: str, spec_dir: str = REFERENCE) -> str:
    """Write raft.tla (bounds rewritten), the vendored libraries, and a
    generated raft.cfg for ``cfg`` into ``out_dir``; returns the cfg
    path.  The spec text comes from the local reference checkout."""
    os.makedirs(out_dir, exist_ok=True)
    spec = open(os.path.join(spec_dir, "raft.tla")).read()
    for name, field in _BOUND_LINES.items():
        val = getattr(cfg.bounds, field)
        spec, n = re.subn(rf"^{name} == .*$", f"{name} == {val}",
                          spec, count=1, flags=re.M)
        if n != 1:
            raise RuntimeError(
                f"bound constant {name} not found in {spec_dir}/raft.tla "
                "— reference layout changed?")
    if cfg.max_inflight_override is not None:
        spec, n = re.subn(r"^MaxInFlightMessages == .*$",
                          f"MaxInFlightMessages == {cfg.max_inflight}",
                          spec, count=1, flags=re.M)
        if n != 1:
            raise RuntimeError("MaxInFlightMessages line not found")
    with open(os.path.join(out_dir, "raft.tla"), "w") as fh:
        fh.write(spec)
    for mod in _LIB_MODULES:
        shutil.copy(os.path.join(spec_dir, mod),
                    os.path.join(out_dir, mod))

    # ---- generated cfg (mirrors tlc_membership/raft.cfg layout) ------
    # Engine server ids are 0-based; TLC model values s1..sN = 1..N.
    names = [f"s{i + 1}" for i in range(cfg.n_servers)]
    init = ", ".join(names[i] for i in cfg.init_servers)
    lines = ["CONSTANTS"]
    lines += [f"    s{i + 1} = {i + 1}" for i in range(cfg.n_servers)]
    lines += [
        "",
        f"    InitServer  = {{{init}}}",
        f"    Server      = {{{', '.join(names)}}}",
        "",
        f"    NumRounds   = {cfg.num_rounds}",
        "    Nil         = 0",
        "",
        f"    Value       = {{{', '.join(map(str, cfg.values))}}}",
        '    ValueEntry  = "ValueEntry"',
        '    ConfigEntry = "ConfigEntry"',
        "",
        '    Follower    = "Follower"',
        '    Candidate   = "Candidate"',
        '    Leader      = "Leader"',
        '    RequestVoteRequest      =   "RequestVoteRequest"',
        '    RequestVoteResponse     =   "RequestVoteResponse"',
        '    AppendEntriesRequest    =   "AppendEntriesRequest"',
        '    AppendEntriesResponse   =   "AppendEntriesResponse"',
        '    CatchupRequest          =   "CatchupRequest"',
        '    CatchupResponse         =   "CatchupResponse"',
        '    CheckOldConfig          =   "CheckOldConfig"',
        "",
    ]
    if cfg.symmetry:
        lines.append("SYMMETRY perms")
    lines += ["VIEW vars", "", "INIT Init", f"NEXT {cfg.next_family}", ""]
    if cfg.constraints or cfg.prefix_pins:
        lines.append("CONSTRAINTS")
        # prefix pins ARE constraints to TLC (raft.cfg:53-55) — the
        # engines compile them to seeds instead (models/golden)
        lines += [f"    {nm}" for nm in
                  tuple(cfg.constraints) + tuple(cfg.prefix_pins)]
        lines.append("")
    if cfg.action_constraints:
        lines.append("ACTION_CONSTRAINTS")
        lines += [f"    {nm}" for nm in cfg.action_constraints]
        lines.append("")
    if cfg.invariants:
        lines.append("INVARIANTS")
        lines += [f"    {nm}" for nm in cfg.invariants]
        lines.append("")
    cfg_path = os.path.join(out_dir, "raft.cfg")
    with open(cfg_path, "w") as fh:
        fh.write("\n".join(lines))
    return cfg_path


def find_java():
    return shutil.which("java")


def find_tla2tools(arg=None):
    for cand in (arg, os.environ.get("TLA2TOOLS_JAR"),
                 "/usr/local/lib/tla2tools.jar",
                 "/opt/tla2tools.jar",
                 os.path.expanduser("~/tla2tools.jar")):
        if cand and os.path.exists(cand):
            return cand
    return None


_RE_DISTINCT = re.compile(
    r"(\d[\d,]*) distinct states found")
_RE_DEPTH = re.compile(r"depth of the complete state graph .*? is (\d+)",
                       re.I)


def run_tlc(model_dir: str, workers: int = 8, java: str = "java",
            jar: str = None, timeout: int = 36000,
            extra_args=()) -> dict:
    """java tlc2.TLC on the emitted model; returns parsed counts+rate.
    TLC has no depth cap flag — bound the space via Bounds/constraints
    in the emitted cfg instead (exactly how the reference does it)."""
    cmd = [java, "-XX:+UseParallelGC", "-cp", jar, "tlc2.TLC",
           "-workers", str(workers), "-deadlock",
           "-config", "raft.cfg", "raft.tla", *extra_args]
    t0 = time.time()
    p = subprocess.run(cmd, cwd=model_dir, capture_output=True,
                       text=True, timeout=timeout)
    secs = time.time() - t0
    out = p.stdout + p.stderr
    m = _RE_DISTINCT.search(out)
    distinct = int(m.group(1).replace(",", "")) if m else None
    md = _RE_DEPTH.search(out)
    return {
        "distinct_states": distinct,
        "depth": int(md.group(1)) if md else None,
        "seconds": round(secs, 2),
        "states_per_sec": (round(distinct / max(secs, 1e-9), 1)
                           if distinct else None),
        "returncode": p.returncode,
        "violation_reported": "Invariant" in out and "violated" in out,
        "raw_tail": out[-2000:],
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cfg", default=os.path.join(REFERENCE, "raft.cfg"),
                    help="reference .cfg to load the model from")
    ap.add_argument("--out", default=None,
                    help="emit dir (default: temp dir)")
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--tla2tools", default=None)
    ap.add_argument("--max-log-length", type=int, default=None)
    ap.add_argument("--max-timeouts", type=int, default=None)
    ap.add_argument("--max-client-requests", type=int, default=None)
    ap.add_argument("--emit-only", action="store_true",
                    help="write the model dir and exit (no TLC run)")
    ap.add_argument("--compare-oracle", action="store_true",
                    help="also run the in-repo Python oracle and "
                         "compare distinct-state counts (small bounds "
                         "only — the oracle is plain Python)")
    args = ap.parse_args(argv)

    from raft_tla_tpu.cfg.parser import load_model
    from raft_tla_tpu.config import Bounds
    cfg = load_model(args.cfg, bounds=None)
    b = cfg.bounds
    if any(v is not None for v in (args.max_log_length,
                                   args.max_timeouts,
                                   args.max_client_requests)):
        def pick(new, old):
            return old if new is None else new       # 0 is a valid bound
        cfg = cfg.with_(bounds=Bounds.make(
            max_log_length=pick(args.max_log_length, b.max_log_length),
            max_restarts=b.max_restarts,
            max_timeouts=pick(args.max_timeouts, b.max_timeouts),
            max_client_requests=pick(args.max_client_requests,
                                     b.max_client_requests),
            max_membership_changes=b.max_membership_changes))

    java, jar = find_java(), find_tla2tools(args.tla2tools)
    if not args.emit_only and (not java or not jar):
        # this image: no Java, zero egress — BASELINE.md documents that
        # the 50x target awaits a Java-equipped host running this tool.
        # Skip BEFORE emitting: emit needs the full reference spec +
        # vendored libraries, which reference-less containers (running
        # on the configs/ cfg twins) don't have either.
        print(json.dumps(dict(
            status="skipped",
            reason=("no java on PATH" if not java
                    else "tla2tools.jar not found (set TLA2TOOLS_JAR)"),
            note="run on a Java-equipped host to record the real TLC "
                 "baseline the 50x target names (BASELINE.md)")))
        return 0

    out_dir = args.out or tempfile.mkdtemp(prefix="tlc_model_")
    cfg_path = emit_tlc_model(cfg, out_dir,
                              spec_dir=os.path.dirname(os.path.abspath(
                                  args.cfg)))
    rec = {"model_dir": out_dir, "cfg": cfg_path}
    if args.emit_only:
        print(json.dumps(dict(rec, status="emitted")))
        return 0

    tlc = run_tlc(out_dir, workers=args.workers, java=java, jar=jar)
    rec.update(status="ran", tlc=tlc)
    if args.compare_oracle:
        from raft_tla_tpu.models.explore import explore
        t0 = time.time()
        r = explore(cfg)
        rec["oracle"] = {
            "distinct_states": int(r.distinct_states),
            "depth": int(r.depth),
            "seconds": round(time.time() - t0, 2)}
        rec["counts_match"] = (
            tlc["distinct_states"] == r.distinct_states)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
