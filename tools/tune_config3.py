"""Config #3 (membership) step-shape tuning harness (VERDICT r4 #1).

Runs the budgeted config-3 workload under candidate engine shapes and
reports rate + the measured per-family enabled maxima (Engine.famx_max)
so FAM_CAPS/FCAP/OCAP can be pre-sized from data instead of the
conservative density table.

Usage: python tools/tune_config3.py VARIANT [budget]
  VARIANT: base | nofp | tightcaps | tight-nofp | chunk4096
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.measure_baseline import build_cfg, ENGINE_KW
from raft_tla_tpu.engine.bfs import Engine


def main():
    variant = sys.argv[1] if len(sys.argv) > 1 else "base"
    budget = int(sys.argv[2]) if len(sys.argv) > 2 else 1_500_000
    cfg = build_cfg(3)
    kw = dict(ENGINE_KW[3])
    inc = True
    if variant == "nofp":
        inc = False
    elif variant == "chunk4096":
        kw["chunk"] = 4096
        kw["fcap"] = 1 << 17
    # ENGINE_KW[3] carries the production fam_caps (a post-construction
    # assignment, not a constructor kwarg — see measure_baseline)
    kw_fam_caps = kw.pop("fam_caps", None)
    if variant.startswith("tight"):
        if TIGHT.get(kw.get("chunk", 2048)) is None:
            raise SystemExit("record famx_max with `base` first")
        # Σ tight caps bounds any chunk's enabled total, so FCAP can
        # shrink with them (fp/probe phases scale with FCAP)
        kw["fcap"] = TIGHT_FCAP[kw.get("chunk", 2048)]
        if variant == "tight-nofp":
            inc = False
    eng = Engine(cfg, store_states=False, incremental_fp=inc, **kw)
    if variant.startswith("tight"):
        # caps measured by a prior `base` run (famx_max + 25% headroom,
        # rounded up to 512); overflow just replays, so tight is safe
        eng.FAM_CAPS = tuple(TIGHT[eng.chunk])
    elif kw_fam_caps is not None and variant != "base":
        eng.FAM_CAPS = tuple(kw_fam_caps)
    t0 = time.time()
    eng.check(max_depth=2)
    compile_s = time.time() - t0
    t0 = time.time()
    r = eng.check(max_states=budget)
    secs = time.time() - t0
    fams = [f.name for f in eng.expander.families]
    print({
        "variant": variant, "budget": budget,
        "distinct": r.distinct_states, "depth": r.depth,
        "seconds": round(secs, 2),
        "states_per_sec": round(r.distinct_states / secs, 1),
        "compile_seconds": round(compile_s, 1),
        "chunk": eng.chunk, "FCAP": eng.FCAP, "OCAP": eng.OCAP,
        "fam_caps": dict(zip(fams, eng.FAM_CAPS)),
        "famx_max": dict(zip(fams, getattr(eng, "famx_max", []))),
    }, flush=True)


# per-chunk tight caps, from the recorded `base` run's famx_max
# (2026-07-31: RequestVote 2650, BecomeLeader 87, ClientRequest 2492,
# AdvanceCommitIndex 1246, AppendEntries 2394, UpdateTerm 1655,
# CocDiscard 689, Receive 6145, Timeout 3431, Restart 6204,
# Duplicate 5767, Drop 5767, AddNewServer 1366, DeleteServer 2394)
TIGHT = {2048: [3584, 512, 3584, 2048, 3072, 2560, 1024, 8192, 4608,
                8192, 7680, 7680, 2048, 3072]}
# Σ famx_max = 42287 bounds any single chunk's enabled total
TIGHT_FCAP = {2048: 45056}

if __name__ == "__main__":
    main()
