"""Tail a run's heartbeat (and optionally its ledger) and render live
progress — the watchdog half of the obs layer.

A long tunneled-TPU run used to be a black box: rounds 4-5 lost
multi-hour runs to dropped tunnels that looked exactly like big
levels.  The engines now rewrite ``--heartbeat FILE`` atomically every
dispatch; this tool reads it (plus the last ``--ledger`` records for
throughput) and prints one status line per interval:

  depth 17  1,642,844 states  5,120/s  last dispatch 4s ago  pid 3406 alive

A heartbeat older than ``--stale`` seconds (default 300 — a slow level
on the tunneled runtime can legitimately take minutes) or a dead pid
flags the run STALLED/DEAD.  Stall detection is also CADENCE-AWARE
(ISSUE 17): once a run has beaten enough times to establish its own
rhythm (>= 5 beats), a heartbeat older than ``--cadence-factor`` times
the observed inter-beat cadence flags ``STALLED?`` even before the
absolute ``--stale`` bound — a dropped TPU tunnel on a fast-beating
run no longer looks identical to one long level.  A supervised run
(``--retries``) in its backoff window renders RETRYING with the
attempt counters instead — alive, not stalled — and a parked batch
job shows status ``parked``.

Multi-job mode: a batch heartbeat (``cli batch`` — the serving layer)
carries a per-job status map; one extra line renders per job:

  job raft-micro: depth 4  29 states  done
  job paxos-micro: depth 3  44 states  running

A batch heartbeat also carries the SLO snapshot (round 13): queue
depth, per-job wait/service-seconds histograms and the executable-
cache counters render as dashboard lines after the job map:

  queue: 3 waiting, 5 done
  wait:    <=0.25s:4 <=1s:1
  service: <=1s:3 <=5s:2
  exec-cache: 2 hits, 1 misses, 1 stored

Daemon mode (``cli serve`` — ISSUE 18): a daemon heartbeat carries a
``daemon`` block (cycle counter, spool queue depths, cumulative
done/rejected, per-tenant rollups); the daemon view renders after the
job/SLO lines:

  daemon serving  cycle 3  incoming 2 claimed 4 done 11 rejected 1
  served 11 jobs (3 cache hits, 0 violations), 1 recovered
  tenant raft: 7 done, 2 cache hits
  tenant paxos: 4 done, 1 cache hit

Two daemon-specific rules: a terminal ``status="done"`` heartbeat (a
graceful drain) renders FINISHED exactly like a batch run's
``finished`` — never a stall — and CADENCE-based stall detection is
skipped while the daemon block says idle|serving|draining, because an
idle daemon legitimately beats at its ``--poll`` rhythm however fast
its serving cadence once was (the absolute ``--stale`` bound still
applies; a dead pid still flags DEAD).

Usage:
  python tools/watch.py HEARTBEAT [--ledger FILE] [--interval SEC]
                        [--stale SEC] [--cadence-factor N] [--once]

``--once`` prints a single line and exits 0 (healthy), 1 (stalled or
dead), 2 (no heartbeat yet) — the shape a cron watchdog wants.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from raft_tla_tpu.obs.heartbeat import read_heartbeat  # noqa: E402


def pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


# the per-dispatch record kinds a throughput estimate may difference
# (meta/resource/retry/job/... rows carry no cumulative state counts)
_DISPATCH_KINDS = ("level", "burst", "sim", "batch")


def last_ledger_records(path, n=2):
    """The last n parseable DISPATCH records of a JSONL ledger (the
    final line can be mid-write — skip anything that does not parse).

    Interleaved/resumed runs demultiplex by the run-id + seq keys
    (ISSUE 17): only records of the newest run id are considered, in
    seq order, so a ledger a resumed run appended to never yields a
    rate computed across two different runs.  Pre-ISSUE-17 rows carry
    neither key and still parse (one unkeyed stream)."""
    recs = []
    try:
        with open(path) as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("kind") in _DISPATCH_KINDS:
                    recs.append(rec)
    except OSError:
        return []
    if not recs:
        return []
    live = recs[-1].get("run_id")
    recs = [r for r in recs if r.get("run_id") == live]
    recs.sort(key=lambda r: r.get("seq", 0))
    return recs[-n:]


def job_lines(hb):
    """One rendered status line per job of a batch heartbeat (the
    serving layer's per-job map); [] for single-run heartbeats."""
    out = []
    for name, j in (hb.get("jobs") or {}).items():
        out.append(f"  job {name}: depth {int(j.get('depth', 0))}  "
                   f"{int(j.get('distinct', 0)):,} states  "
                   f"{j.get('status', '?')}")
    return out


def wave_lines(hb):
    """The batched wave's occupancy line (rounds 16-17 mesh waves):
    the device grid, how many lanes hold real jobs, and the idle-lane
    waste as ``pad N/M``; [] when the heartbeat carries no wave block
    (solo runs, cache-only batches).  Renders in the batch AND the
    daemon views — the block rides every batched dispatch beat either
    way.  Under a 2-D (jobs, state) mesh the grid and the state-shard
    count render explicitly:

      wave: 4 devices x 2 lanes/device  6 jobs  pad 2/8
      wave: 2x2 grid  6 jobs  pad 2/8  state shards 2
    """
    w = hb.get("wave")
    if not w:
        return []
    dev = int(w.get("devices", 1))
    lanes = int(w.get("lanes", 0))
    ss = int(w.get("state_shards", 1))
    filled = int(w.get("filled", 0))
    pad = int(w.get("pad", 0))
    if ss > 1:
        return [f"  wave: {dev // ss}x{ss} grid  {filled} jobs  "
                f"pad {pad}/{lanes}  state shards {ss}"]
    return [f"  wave: {dev} device{'s' if dev != 1 else ''} x "
            f"{int(w.get('jobs_per_device', lanes))} lanes/device  "
            f"{filled} jobs  pad {pad}/{lanes}"]


def _hist_summary(hist):
    """'<=0.25s:3 <=1s:2 >120s:1' — only the occupied buckets, in
    edge order (the heartbeat keeps the full fixed-bucket histogram;
    'inf' is the catch-all above the largest edge)."""
    out = []
    last_edge = "?"
    for k, v in (hist or {}).items():
        if k.startswith("le_"):
            last_edge = k[3:]
            if v:
                out.append(f"<={last_edge}s:{v}")
        elif k == "inf" and v:
            out.append(f">{last_edge}s:{v}")
    return " ".join(out)


def slo_lines(hb):
    """The serving layer's SLO snapshot (queue depth, wait/service
    histograms, exec-cache counters) as rendered dashboard lines; []
    when the heartbeat carries none."""
    slo = hb.get("slo")
    if not slo:
        return []
    out = [f"  queue: {int(slo.get('queue_depth', 0))} waiting, "
           f"{int(slo.get('jobs_done', 0))} done"]
    w = _hist_summary(slo.get("wait_hist"))
    s = _hist_summary(slo.get("service_hist"))
    if w:
        out.append(f"  wait:    {w}")
    if s:
        out.append(f"  service: {s}")
    ec = slo.get("exec_cache")
    if ec:
        out.append(
            f"  exec-cache: {int(ec.get('exec_cache_hits', 0))} hits, "
            f"{int(ec.get('exec_cache_misses', 0))} misses, "
            f"{int(ec.get('exec_cache_stores', 0))} stored"
            + (f", {int(ec['exec_cache_store_failures'])} store "
               f"failures (backend cannot serialize?)"
               if ec.get("exec_cache_store_failures") else ""))
    return out


def daemon_lines(hb):
    """The daemon view (``cli serve`` heartbeats): queue depths,
    cumulative serve counters, per-tenant rollups and the drain
    reason; [] for non-daemon heartbeats."""
    d = hb.get("daemon")
    if not d:
        return []
    out = [f"  daemon {d.get('status', '?')}  "
           f"cycle {int(d.get('cycles', 0))}  "
           f"incoming {int(d.get('incoming', 0))} "
           f"claimed {int(d.get('claimed', 0))} "
           f"done {int(d.get('done', 0))} "
           f"rejected {int(d.get('rejected', 0))}"]
    served = (f"  served {int(d.get('jobs_done', 0))} jobs "
              f"({int(d.get('cache_hits', 0))} cache hits, "
              f"{int(d.get('violations', 0))} violations)")
    if d.get("jobs_recovered"):
        served += f", {int(d['jobs_recovered'])} recovered"
    out.append(served)
    for name, t in (d.get("tenants") or {}).items():
        out.append(f"  tenant {name}: {int(t.get('jobs_done', 0))} "
                   f"done, {int(t.get('cache_hits', 0))} cache hits"
                   + (f", {int(t['violations'])} violations"
                      if t.get("violations") else ""))
    if d.get("drain_reason"):
        out.append(f"  draining: {d['drain_reason']}")
    return out


# a run must beat this many times before its own cadence is trusted
# for stall detection (too few samples and one slow early level —
# compile included — would poison the estimate)
MIN_CADENCE_BEATS = 5
# never flag on cadence alone under this age: sub-second-cadence
# micro runs would flap on ordinary scheduler hiccups
CADENCE_FLOOR_S = 30.0


def observed_cadence(hb):
    """Mean inter-beat seconds of this heartbeat's own history, or
    None before MIN_CADENCE_BEATS (the heartbeat carries started_ts /
    last_dispatch_ts / beats, so the cadence needs no extra state)."""
    beats = int(hb.get("beats", 0))
    if beats < MIN_CADENCE_BEATS:
        return None
    span = hb["last_dispatch_ts"] - hb.get("started_ts",
                                           hb["last_dispatch_ts"])
    if span <= 0:
        return None
    return span / (beats - 1)


def status_line(hb_path, ledger_path, stale_s, cadence_factor=8.0):
    """(line, exit_code): 0 healthy, 1 stalled/dead, 2 unreadable.
    Batch heartbeats append one line per job (job_lines)."""
    try:
        hb = read_heartbeat(hb_path)
    except (OSError, ValueError) as e:
        return f"no heartbeat yet ({e})", 2
    age = time.time() - hb["last_dispatch_ts"]
    alive = pid_alive(int(hb["pid"]))
    # "finished" is a run's terminal beat; "done" is a daemon's
    # graceful drain — both terminal, both render FINISHED so the
    # watch loop exits 0 instead of flagging a stall on a process
    # that exited exactly as asked
    finished = hb.get("status") in ("finished", "done")
    backoff = hb.get("status") == "backoff"
    # a live daemon (idle|serving|draining) beats at its --poll
    # rhythm while idle: its historical serving cadence says nothing
    # about the gaps between idle beats, so cadence-based stall
    # detection is meaningless — the absolute --stale bound and the
    # pid check still guard a daemon that truly wedged
    daemonish = hb.get("daemon") is not None and \
        hb.get("status") in ("idle", "serving", "draining")
    parts = [f"depth {hb['depth']}",
             f"{hb['states_enqueued']:,} states"]
    rate = None
    if ledger_path:
        recs = last_ledger_records(ledger_path)
        if len(recs) == 2:
            ds = (recs[1].get("distinct_states",
                              recs[1].get("walker_steps", 0)) -
                  recs[0].get("distinct_states",
                              recs[0].get("walker_steps", 0)))
            dt = recs[1].get("seconds", 0) - recs[0].get("seconds", 0)
            if dt > 0:
                rate = ds / dt
        elif len(recs) == 1:
            rate = recs[0].get("states_per_sec")
    if rate is not None:
        parts.append(f"{rate:,.0f}/s")
    parts.append(f"last dispatch {age:.0f}s ago")
    cadence = observed_cadence(hb)
    cadence_limit = None
    if cadence is not None and cadence_factor and not daemonish:
        cadence_limit = max(cadence * cadence_factor, CADENCE_FLOOR_S)
    code = 0
    if finished:
        parts.append("FINISHED")
    elif backoff and alive:
        # supervised retry (resil/supervisor): the run hit a transient
        # failure and is waiting out its backoff — alive and healthy,
        # not stalled, however old the last dispatch is.  A DEAD pid
        # still wins below: a run killed during its backoff window
        # must flag DEAD, not an eternal RETRYING.
        r = hb.get("retry") or {}
        parts.append(
            f"RETRYING attempt {r.get('attempt', '?')}/"
            f"{r.get('max_attempts', '?')}, backoff "
            f"{r.get('wait_s', '?')}s")
    elif not alive:
        parts.append(f"pid {hb['pid']} DEAD")
        code = 1
    elif age > stale_s:
        parts.append(f"pid {hb['pid']} alive but STALLED? "
                     f"(> {stale_s:.0f}s since last dispatch)")
        code = 1
    elif cadence_limit is not None and age > cadence_limit:
        # the run's own rhythm says this gap is abnormal even though
        # the absolute --stale bound has not yet tripped: a dropped
        # tunnel on a fast-beating run surfaces in minutes, not hours
        parts.append(
            f"pid {hb['pid']} alive but STALLED? ({age:.0f}s "
            f"> {cadence_factor:.0f}x observed cadence "
            f"{cadence:.1f}s/beat over {hb.get('beats', 0)} beats)")
        code = 1
    else:
        parts.append(f"pid {hb['pid']} alive")
    line = "  ".join(parts)
    jl = (job_lines(hb) + wave_lines(hb) + slo_lines(hb) +
          daemon_lines(hb))
    if jl:
        line = "\n".join([line] + jl)
    return line, code


def main(argv=None):
    args = list(sys.argv[1:] if argv is None else argv)
    if not args or args[0] in ("-h", "--help"):
        print(__doc__)
        return 0 if args else 2
    hb_path = args.pop(0)
    once = "--once" in args
    if once:
        args.remove("--once")
    opts = dict(zip(args[::2], args[1::2]))
    bad = set(opts) - {"--ledger", "--interval", "--stale",
                       "--cadence-factor"}
    if bad or len(args) % 2:
        raise SystemExit(f"unknown/incomplete options: "
                         f"{sorted(bad) or args[-1:]}")
    ledger = opts.get("--ledger")
    interval = float(opts.get("--interval", 5))
    stale = float(opts.get("--stale", 300))
    factor = float(opts.get("--cadence-factor", 8))
    if once:
        line, code = status_line(hb_path, ledger, stale, factor)
        print(line)
        return code
    while True:
        line, code = status_line(hb_path, ledger, stale, factor)
        print(time.strftime("%H:%M:%S") + "  " + line, flush=True)
        if "FINISHED" in line:
            return 0
        time.sleep(interval)


if __name__ == "__main__":
    sys.exit(main())
