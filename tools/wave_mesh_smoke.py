"""CI mesh-wave gate (rounds 16+17): `cli batch --wave-mesh` e2e.

One 4-job raft micro wave runs three times through the real CLI under
FORCED 4 virtual CPU devices (``--xla_force_host_platform_device_count``
— the same trick tests/test_pjit.py and the pjit smoke use, so the
device count is identical in every run and only ``--wave-mesh``
differs):

- run A: ``--wave-mesh 4`` — the job axis sharded across the mesh.
  The summary and the ``--registry`` record must stamp
  ``wave_devices=4`` (the occupancy counters ride ``rep.summary`` into
  the record), and every job must complete batched (no fallbacks).
- run B: ``--wave-mesh off`` — the single-device reference.  Per-job
  counts, depths and level sizes must be bit-identical to run A's.
- run C: ``--wave-mesh 2x2`` — the round-17 two-axis grid on the SAME
  4 devices: jobs across 2 rows, each job's state tables split across
  2 shards.  Same per-job bit-exactness, and the summary + registry
  record stamp ``wave_state_shards=2`` next to ``wave_devices=4``.

Run A also stores its bucket executable in a fresh
``--executable-cache``; runs B and C share that cache and must NOT
load it: the mesh shape — the [J, S] grid, not just the device count
— is part of the executable key (serve/exec_cache), so a
differently-meshed executable reads as a named miss: B and C each
report zero exec-cache hits and exactly one ``bucket_compile`` span
of their own.  A wrong load here would be silent corruption; the
named miss is the contract.
"""

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_batch(jobs_path, extra, tag, tmp):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "") +
                          " --xla_force_host_platform_device_count=4"
                          ).strip())
    stats = os.path.join(tmp, f"stats_{tag}.json")
    tl = os.path.join(tmp, f"tl_{tag}.json")
    p = subprocess.run(
        [sys.executable, "-m", "raft_tla_tpu", "batch",
         "--jobs", jobs_path, "--stats-json", stats,
         "--trace-timeline", tl, *extra],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600)
    assert p.returncode == 0, (tag, p.returncode, p.stdout, p.stderr)
    with open(stats) as fh:
        payload = json.load(fh)
    return payload["summary"], payload["jobs"], tl


def span_count(timeline_path, name):
    with open(timeline_path) as fh:
        return fh.read().count(f'"name": "{name}"')


def main():
    tmp = tempfile.mkdtemp(prefix="wave_mesh_smoke_")
    jobs_path = os.path.join(tmp, "jobs.jsonl")
    with open(jobs_path, "w") as fh:
        for d in (2, 3, 4, 5):
            fh.write(json.dumps({
                "spec": "raft",
                "config": "configs/tlc_membership/raft.cfg",
                "overrides": {
                    "servers": 2, "values": [1], "max_inflight": 4,
                    "next": "NextAsync",
                    "bounds": {"max_log_length": 1, "max_timeouts": 1,
                               "max_client_requests": 1}},
                "max_depth": d, "label": f"r{d}"}) + "\n")
    registry = os.path.join(tmp, "registry")
    exec_dir = os.path.join(tmp, "exec")

    # run A: the 4-device job mesh
    sA, rowsA, tlA = run_batch(
        jobs_path, ("--wave-mesh", "4", "--registry", registry,
                    "--executable-cache", exec_dir), "mesh", tmp)
    assert sA["wave_devices"] == 4, sA
    assert sA["wave_lanes"] == 4, sA        # 4 jobs on 4 devices
    assert sA["fallback_jobs"] == 0, sA
    assert all(r["status"] == "done" for r in rowsA), rowsA

    # wave_devices=4 must be stamped in the registry record
    recs = []
    for nm in sorted(os.listdir(registry)):
        if nm.endswith(".json"):
            with open(os.path.join(registry, nm)) as fh:
                recs.append(json.load(fh))
    assert len(recs) == 1 and recs[0]["cmd"] == "batch", recs
    assert recs[0]["counters"]["wave_devices"] == 4, recs[0]["counters"]
    assert recs[0]["counters"]["wave_lanes"] == 4, recs[0]["counters"]

    # run B: single-device reference, SAME exec cache — the mesh-keyed
    # executable must read as a miss (named, never a wrong load)
    sB, rowsB, tlB = run_batch(
        jobs_path, ("--wave-mesh", "off",
                    "--executable-cache", exec_dir), "single", tmp)
    assert sB["wave_devices"] == 1, sB
    assert sB.get("exec_cache_hits", 0) == 0, \
        f"a 4-device executable must never answer a single-device " \
        f"wave: {sB}"
    assert span_count(tlB, "bucket_compile") == 1, \
        "the single-device run must compile its own bucket"

    # count parity per job, bit-exact across modes
    assert len(rowsA) == len(rowsB) == 4
    for a, b in zip(rowsA, rowsB):
        assert (a["label"], a["distinct_states"],
                a["generated_states"], a["depth"],
                a["level_sizes"]) == \
               (b["label"], b["distinct_states"],
                b["generated_states"], b["depth"],
                b["level_sizes"]), (a, b)

    # run C: the 2x2 jobs x state grid on the same 4 devices, still
    # sharing run A's exec cache — [2, 2] vs [4, 1] is a different
    # GSPMD program, so another named miss and its own compile
    sC, rowsC, tlC = run_batch(
        jobs_path, ("--wave-mesh", "2x2", "--registry", registry,
                    "--executable-cache", exec_dir), "grid", tmp)
    assert sC["wave_devices"] == 4, sC
    assert sC["wave_state_shards"] == 2, sC
    assert sC["wave_lanes"] == 4, sC        # 4 jobs on the J=2 axis
    assert sC["fallback_jobs"] == 0, sC
    assert sC.get("exec_cache_hits", 0) == 0, \
        f"a 4x1 executable must never answer a 2x2 wave: {sC}"
    assert span_count(tlC, "bucket_compile") == 1, \
        "the 2x2 run must compile its own bucket"
    for b, c in zip(rowsB, rowsC):
        assert (b["label"], b["distinct_states"],
                b["generated_states"], b["depth"],
                b["level_sizes"]) == \
               (c["label"], c["distinct_states"],
                c["generated_states"], c["depth"],
                c["level_sizes"]), (b, c)

    # the grid run's registry record stamps the state axis
    recs = []
    for nm in sorted(os.listdir(registry)):
        if nm.endswith(".json"):
            with open(os.path.join(registry, nm)) as fh:
                recs.append(json.load(fh))
    assert len(recs) == 2, recs
    grid = [r for r in recs
            if r["counters"].get("wave_state_shards", 0) == 2]
    assert len(grid) == 1, [r["counters"] for r in recs]
    assert grid[0]["counters"]["wave_devices"] == 4, grid[0]

    print("wave_mesh_smoke: OK (4-device mesh wave == 2x2 grid wave "
          "== single-device reference per job; wave_devices=4 in "
          "summary + registry, wave_state_shards=2 for the grid; "
          "mesh-shape change = named exec-cache miss)")


if __name__ == "__main__":
    main()
