"""CI mesh-wave gate (round 16): `cli batch --wave-mesh` end-to-end.

One 4-job raft micro wave runs twice through the real CLI under
FORCED 4 virtual CPU devices (``--xla_force_host_platform_device_count``
— the same trick tests/test_pjit.py and the pjit smoke use, so the
device count is identical in both runs and only ``--wave-mesh``
differs):

- run A: ``--wave-mesh 4`` — the job axis sharded across the mesh.
  The summary and the ``--registry`` record must stamp
  ``wave_devices=4`` (the occupancy counters ride ``rep.summary`` into
  the record), and every job must complete batched (no fallbacks).
- run B: ``--wave-mesh off`` — the single-device reference.  Per-job
  counts, depths and level sizes must be bit-identical to run A's.

Run A also stores its bucket executable in a fresh
``--executable-cache``; run B shares that cache and must NOT load it:
the mesh shape is part of the executable key (serve/exec_cache), so a
differently-meshed executable reads as a named miss — run B reports
zero exec-cache hits and exactly one ``bucket_compile`` span of its
own.  A wrong load here would be silent corruption; the named miss is
the contract.
"""

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_batch(jobs_path, extra, tag, tmp):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "") +
                          " --xla_force_host_platform_device_count=4"
                          ).strip())
    stats = os.path.join(tmp, f"stats_{tag}.json")
    tl = os.path.join(tmp, f"tl_{tag}.json")
    p = subprocess.run(
        [sys.executable, "-m", "raft_tla_tpu", "batch",
         "--jobs", jobs_path, "--stats-json", stats,
         "--trace-timeline", tl, *extra],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600)
    assert p.returncode == 0, (tag, p.returncode, p.stdout, p.stderr)
    with open(stats) as fh:
        payload = json.load(fh)
    return payload["summary"], payload["jobs"], tl


def span_count(timeline_path, name):
    with open(timeline_path) as fh:
        return fh.read().count(f'"name": "{name}"')


def main():
    tmp = tempfile.mkdtemp(prefix="wave_mesh_smoke_")
    jobs_path = os.path.join(tmp, "jobs.jsonl")
    with open(jobs_path, "w") as fh:
        for d in (2, 3, 4, 5):
            fh.write(json.dumps({
                "spec": "raft",
                "config": "configs/tlc_membership/raft.cfg",
                "overrides": {
                    "servers": 2, "values": [1], "max_inflight": 4,
                    "next": "NextAsync",
                    "bounds": {"max_log_length": 1, "max_timeouts": 1,
                               "max_client_requests": 1}},
                "max_depth": d, "label": f"r{d}"}) + "\n")
    registry = os.path.join(tmp, "registry")
    exec_dir = os.path.join(tmp, "exec")

    # run A: the 4-device job mesh
    sA, rowsA, tlA = run_batch(
        jobs_path, ("--wave-mesh", "4", "--registry", registry,
                    "--executable-cache", exec_dir), "mesh", tmp)
    assert sA["wave_devices"] == 4, sA
    assert sA["wave_lanes"] == 4, sA        # 4 jobs on 4 devices
    assert sA["fallback_jobs"] == 0, sA
    assert all(r["status"] == "done" for r in rowsA), rowsA

    # wave_devices=4 must be stamped in the registry record
    recs = []
    for nm in sorted(os.listdir(registry)):
        if nm.endswith(".json"):
            with open(os.path.join(registry, nm)) as fh:
                recs.append(json.load(fh))
    assert len(recs) == 1 and recs[0]["cmd"] == "batch", recs
    assert recs[0]["counters"]["wave_devices"] == 4, recs[0]["counters"]
    assert recs[0]["counters"]["wave_lanes"] == 4, recs[0]["counters"]

    # run B: single-device reference, SAME exec cache — the mesh-keyed
    # executable must read as a miss (named, never a wrong load)
    sB, rowsB, tlB = run_batch(
        jobs_path, ("--wave-mesh", "off",
                    "--executable-cache", exec_dir), "single", tmp)
    assert sB["wave_devices"] == 1, sB
    assert sB.get("exec_cache_hits", 0) == 0, \
        f"a 4-device executable must never answer a single-device " \
        f"wave: {sB}"
    assert span_count(tlB, "bucket_compile") == 1, \
        "the single-device run must compile its own bucket"

    # count parity per job, bit-exact across modes
    assert len(rowsA) == len(rowsB) == 4
    for a, b in zip(rowsA, rowsB):
        assert (a["label"], a["distinct_states"],
                a["generated_states"], a["depth"],
                a["level_sizes"]) == \
               (b["label"], b["distinct_states"],
                b["generated_states"], b["depth"],
                b["level_sizes"]), (a, b)

    print("wave_mesh_smoke: OK (4-device mesh wave == single-device "
          "reference per job; wave_devices=4 in summary + registry; "
          "mesh-shape change = named exec-cache miss)")


if __name__ == "__main__":
    main()
